"""Block / HybridBlock (reference: ``python/mxnet/gluon/block.py``).

``HybridBlock.hybridize()`` is the reference's bridge from imperative code to
the compiled world (trace → nnvm graph → ``CachedOp`` with static memory
planning, ``src/imperative/cached_op.cc``). The TPU design stages the same
trace into ``jax.jit`` instead:

  - first call runs eagerly (triggers deferred parameter init, like the
    reference's shape-inference-on-first-forward);
  - subsequent calls hit a jitted pure function keyed on (input shapes,
    dtypes, train-mode) — the jit cache is the analog of CachedOp's
    per-signature graph cache and of bucketing;
  - parameters enter as traced arguments (not baked constants), so one
    compiled program serves every optimizer step;
  - stochastic layers draw from a per-call PRNG key argument
    (``random.trace_key_scope``), keeping eager and hybrid runs reproducible;
  - in-trace state writes (BatchNorm running stats) are collected on a state
    tape and returned as extra outputs, then written back concretely —
    replacing the reference's mutable aux-state kernels functionally.

Eager-vs-hybridized equivalence is the core test invariant (SURVEY §4).
"""
from __future__ import annotations

import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp

from .. import autograd as _ag
from .. import ndarray as nd
from .. import random as _rng
from ..base import MXNetError
from ..ndarray import NDArray
from .parameter import DeferredInitializationError, Parameter, ParameterDict

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope:
    """Naming scope: generates unique prefixes like the reference."""

    _tls = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._tls, "current", None)
        if current is None:
            if prefix is None:
                prefix = _global_count(hint)
            return prefix, ParameterDict(prefix, shared=params)
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = f"{hint}{count}_"
        full = current._block.prefix + prefix
        shared = params if params is not None else current._block._params._shared
        return full, ParameterDict(full, shared=shared)

    def __enter__(self):
        self._old = getattr(_BlockScope._tls, "current", None)
        _BlockScope._tls.current = self
        return self

    def __exit__(self, *exc):
        _BlockScope._tls.current = self._old


_GLOBAL_COUNT = {}
_NAME_LOCK = threading.Lock()

# global-policy epoch folded into every jit-cache signature: bumped when a
# process-wide compile-affecting policy flips (e.g. amp.init), so programs
# traced under the old policy are not replayed under the new one
_CACHE_EPOCH = [0]
_EPOCH_LOCK = threading.Lock()


def bump_global_cache_epoch():
    # amp.init/_reset may flip the policy from a worker thread while other
    # threads read the epoch into jit-cache keys (JH005)
    with _EPOCH_LOCK:
        _CACHE_EPOCH[0] += 1


def _global_count(hint):
    # blocks may be constructed from loader/serving threads (JH005)
    with _NAME_LOCK:
        n = _GLOBAL_COUNT.get(hint, 0)
        _GLOBAL_COUNT[hint] = n + 1
    return f"{hint}{n}_"


# state tape for in-trace parameter writes (BatchNorm moving stats)
class _TraceState(threading.local):
    def __init__(self):
        self.active = False
        self.updates = []  # list[(Parameter, raw)]
        self.force_eager = False  # deferred-init pass: children must not jit
        self.symbolic = False  # export pass: hybrid_forward sees the sym namespace


_TRACE = _TraceState()

_DUMMY_KEY = None


def _dummy_key():
    """Fixed key for traced programs that never draw randomness."""
    global _DUMMY_KEY
    if _DUMMY_KEY is None:
        _DUMMY_KEY = jax.random.key(0)
    return _DUMMY_KEY


def record_state_update(param, new_raw):
    """Layers call this instead of assigning ``param.data()._data`` directly."""
    if _TRACE.active:
        _TRACE.updates.append((param, new_raw))
    else:
        param._nd._data = jax.lax.stop_gradient(
            new_raw._data if isinstance(new_raw, NDArray) else new_raw)


def _flatten_nds(out):
    """Flatten nested (tuple/list) NDArray outputs -> (raw_list, rebuild_fn)."""
    raws = []

    def walk(o):
        if isinstance(o, NDArray):
            raws.append(o._data)
            return ("nd", len(raws) - 1)
        if isinstance(o, (tuple, list)):
            return (type(o).__name__, [walk(x) for x in o])
        return ("const", o)

    spec = walk(out)

    def rebuild(new_raws, spec=spec):
        def un(s):
            kind = s[0]
            if kind == "nd":
                v = new_raws[s[1]]
                return v if isinstance(v, NDArray) else NDArray(v)
            if kind in ("tuple", "list"):
                seq = [un(x) for x in s[1]]
                return tuple(seq) if kind == "tuple" else seq
            return s[1]

        return un(spec)

    return raws, rebuild


def _resolve_remat_policy(remat):
    """Normalize a ``hybridize(remat=...)`` value to a jax.checkpoint policy.

    ``True``/``'full'`` → save nothing (recompute everything in backward);
    a string names a ``jax.checkpoint_policies`` member (``'dots_saveable'``,
    ``'nothing_saveable'``, ``'dots_with_no_batch_dims_saveable'``, ...);
    a callable passes through as a custom policy.
    """
    if remat is True or remat == "full":
        return None  # jax.checkpoint default: save nothing
    if callable(remat):
        return remat
    if isinstance(remat, str):
        pol = getattr(jax.checkpoint_policies, remat, None)
        if pol is None:
            avail = [n for n in dir(jax.checkpoint_policies)
                     if not n.startswith("_")]
            raise ValueError(f"unknown remat policy {remat!r}; available: "
                             f"'full', {avail}")
        return pol
    raise ValueError(f"remat= must be True, 'full', a jax.checkpoint_policies "
                     f"name, or a callable policy, got {remat!r}")


class Block:
    """Base container: parameter registration + eager forward."""

    # classes that form a rematerialization unit under ``hybridize(remat=)``
    # (one jax.checkpoint per instance): the transformer/GPT-2/BERT layer
    # stacks set this True so long-context training trades flops for peak
    # activation memory deliberately (docs/PERFORMANCE.md "Mixed precision")
    _remat_unit = False

    def __init__(self, prefix=None, params=None):
        self._empty_init_done = True
        self._prefix, self._params = _BlockScope.create(prefix, params, self._alias())
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = OrderedDict()
        self._forward_hooks = []
        self._forward_pre_hooks = []
        self._remat = None

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._prefix.rstrip("_")

    @property
    def params(self):
        return self._params

    def name_scope(self):
        return self._scope

    # -- attribute-based registration ---------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            existing = self.__dict__.get("_reg_params")
            if existing is not None:
                existing[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    # -- parameter management -----------------------------------------------
    def collect_params(self, select=None):
        import re

        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self._params)
        else:
            pat = re.compile(select)
            ret.update({k: v for k, v in self._params.items() if pat.match(k)})
        for child in self._children.values():
            ret.update(child.collect_params(select))
        # tied parameters (params= sharing) appear under each sharer's local
        # name — keep the first occurrence only, so Trainer/optimizer see one
        # entry (no double state, no double allreduce contribution)
        seen = set()
        for k in list(ret.keys()):
            pid = id(ret[k])
            if pid in seen:
                ret.pop(k)
            else:
                seen.add(pid)
        return ret

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        self.collect_params().initialize(init=init, ctx=ctx, force_reinit=force_reinit)
        return self

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for p in self._params.values():
            p.cast(dtype)
        return self

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    # -- structural (prefix-independent) serialization -----------------------
    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + n: p for n, p in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def save_parameters(self, filename, deduplicate=False):
        from ..serialization import save_ndarrays

        params = self._collect_params_with_prefix()
        save_ndarrays(filename, {k: p.data() for k, p in params.items() if p._nd is not None})

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False, dtype_source="current"):
        from ..serialization import load_ndarrays

        loaded = load_ndarrays(filename)
        params = self._collect_params_with_prefix()
        for name, p in params.items():
            if name in loaded:
                p.set_data(loaded[name])
            elif not allow_missing:
                raise MXNetError(f"Parameter {name} missing in {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(params)
            if extra:
                raise MXNetError(f"{filename} contains unknown parameters {sorted(extra)[:5]}")

    # pytorch-style aliases used by some reference-era scripts
    save_params = save_parameters

    def load_params(self, filename, ctx=None, **kw):
        self.load_parameters(filename, ctx=ctx, **kw)

    # -- call ---------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def hybridize(self, active=True, **kwargs):
        # remat threads recursively: every block stores the policy, but only
        # ``_remat_unit`` classes actually wrap their forward in
        # jax.checkpoint (one unit per layer, no nesting in the model zoos).
        # remat=False clears; remat=None (absent) leaves the setting alone.
        r = kwargs.get("remat", None)
        if r is not None:
            self._remat = None if r is False else r
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def summary(self, *inputs):
        out = self(*inputs)
        nparams = sum(p.data().size for p in self.collect_params().values() if p._nd is not None)
        print(f"{self.__class__.__name__}: {nparams} parameters")
        return out

    def __repr__(self):
        lines = [f"{self.__class__.__name__}("]
        for name, child in self._children.items():
            body = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({name}): {body}")
        lines.append(")")
        return "\n".join(lines)


class _HybridTrace:
    """Context: swap params to tracers, bind RNG + train-mode, collect state."""

    def __init__(self, params, raws, train, key):
        self.params = params
        self.raws = raws
        self.train = train
        self.key = key

    def __enter__(self):
        self._saved = [p._nd._data for p in self.params]
        for p, r in zip(self.params, self.raws):
            p._nd._data = r
        self._ag_scope = _ag._RecordScope(False, self.train)
        self._ag_scope.__enter__()
        self._key_scope = _rng.trace_key_scope(self.key)
        self._key_scope.__enter__()
        self._trace_was = (_TRACE.active, _TRACE.updates)
        _TRACE.active, _TRACE.updates = True, []
        return self

    def __exit__(self, *exc):
        self.state_updates = _TRACE.updates
        _TRACE.active, _TRACE.updates = self._trace_was
        self._key_scope.__exit__(*exc)
        self.rng_uses = self._key_scope.uses
        self._ag_scope.__exit__(*exc)
        for p, s in zip(self.params, self._saved):
            p._nd._data = s


class HybridBlock(Block):
    """Block whose forward can be staged into one XLA computation."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._jit_cache = {}
        self._static_alloc = False

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  inline_limit=2, forward_bulk_size=None, backward_bulk_size=None,
                  remat=None):
        """``remat=`` installs an activation-rematerialization policy on this
        block and its children: ``True``/``'full'`` (recompute everything),
        a ``jax.checkpoint_policies`` name such as ``'dots_saveable'``, or a
        callable; ``False`` clears it. Applied as ``jax.checkpoint`` around
        each ``_remat_unit`` layer when the forward is staged (TrainStep or
        a hybridized jit) — set it BEFORE building a TrainStep, whose
        program cache does not watch this flag."""
        self._active = active
        self._static_alloc = static_alloc  # maps to buffer donation (future)
        if remat is not None:
            if remat is not False:
                _resolve_remat_policy(remat)  # validate eagerly
            self._remat = None if remat is False else remat
        self._jit_cache.clear()
        super().hybridize(active, remat=remat)

    def infer_shape(self, *args):
        """Hook for deferred-init shape inference; layers override."""
        raise DeferredInitializationError(
            f"{self.__class__.__name__} has deferred-initialized parameters and "
            "no infer_shape; run one eager forward or initialize with full shapes")

    # -- hybrid_forward plumbing --------------------------------------------
    def forward(self, x, *args, **kwargs):
        if _TRACE.symbolic:
            from .. import symbol as sym_mod

            params = {name: p.var() for name, p in self._reg_params.items()}
            return self.hybrid_forward(sym_mod, x, *args, **params, **kwargs)
        params = {}
        try:
            for name, p in self._reg_params.items():
                params[name] = p.data()
        except DeferredInitializationError:
            self._deferred_infer(x, *args)
            params = {name: p.data() for name, p in self._reg_params.items()}
        return self.hybrid_forward(nd, x, *args, **params, **kwargs)

    def _deferred_infer(self, *args):
        self.infer_shape(*args)
        for p in self._reg_params.values():
            if p._deferred_init is not None:
                p._finish_deferred_init(p.shape)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # -- staged call --------------------------------------------------------
    def __call__(self, *args, **kwargs):
        if (self._remat is not None and type(self)._remat_unit
                and _TRACE.active and not _TRACE.force_eager
                and not _TRACE.symbolic):
            # inside a staged trace (TrainStep loss or a hybridized jit):
            # wrap this layer in jax.checkpoint so its activations are
            # recomputed, not saved, during backward
            return self._call_remat(args, kwargs)
        if (not self._active or _TRACE.active or _TRACE.force_eager
                or _TRACE.symbolic or kwargs):
            return super().__call__(*args, **kwargs)
        return self._call_cached(*args)

    def _call_remat(self, args, kwargs):
        """Run this block's forward under ``jax.checkpoint`` with the
        installed policy. Parameters and NDArray arguments enter as explicit
        checkpoint inputs (differentiation-correct); non-array arguments
        (None masks, python flags) ride the closure. Blocks that record
        state updates (BatchNorm) must not be remat units — the state tape
        would leak tracers out of the checkpointed trace."""
        policy = _resolve_remat_policy(self._remat)
        plist = [p for _, p in sorted(self.collect_params().items())]
        if any(p._nd is None for p in plist):
            return Block.__call__(self, *args, **kwargs)  # deferred init
        param_raws = tuple(p._nd._data for p in plist)
        nd_idx = [i for i, a in enumerate(args) if isinstance(a, NDArray)]
        arg_raws = tuple(args[i]._data for i in nd_idx)
        cell = {}

        def fn(praws, araws):
            saved = [p._nd._data for p in plist]
            for p, r in zip(plist, praws):
                p._nd._data = r
            try:
                call_args = list(args)
                for i, r in zip(nd_idx, araws):
                    call_args[i] = NDArray(r)
                out = Block.__call__(self, *call_args, **kwargs)
            finally:
                for p, s in zip(plist, saved):
                    p._nd._data = s
            raws, rebuild = _flatten_nds(out)
            cell["rebuild"] = rebuild
            return tuple(raws)

        out_raws = jax.checkpoint(fn, policy=policy)(param_raws, arg_raws)
        return cell["rebuild"]([NDArray(r) for r in out_raws])

    def _call_cached(self, *args):
        plist = [p for _, p in sorted(self.collect_params().items())]
        if any(p._nd is None for p in plist):
            # first call runs eagerly to trigger deferred init (reference
            # semantics: shape inference happens on first forward). Children
            # must not stage their own jits during this pass — it would
            # fragment compilation and consume PRNG keys out of order.
            _TRACE.force_eager = True
            try:
                return super().__call__(*args)
            finally:
                _TRACE.force_eager = False
        return self._run_jit(plist, args)

    def _run_jit(self, plist, args):
        arg_raws = [a._data if isinstance(a, NDArray) else a for a in args]
        train = _ag.is_training()
        sig = (train, _CACHE_EPOCH[0], tuple(
            (tuple(r.shape), str(r.dtype)) if hasattr(r, "shape") else ("py", repr(r))
            for r in arg_raws))
        entry = self._jit_cache.get(sig)
        if entry is None:
            entry = self._build_jit(plist, args, train)
            self._jit_cache[sig] = entry
        jfn, rebuild_cell, nstate_cell = entry
        # only consume global RNG state if the traced program draws from it —
        # keeps eager and hybridized key chains aligned for deterministic nets
        key = _rng.next_key() if nstate_cell.get("uses_rng", False) else _dummy_key()
        param_raws = tuple(p._nd._data for p in plist)
        out_raws, state_raws = jfn(param_raws, tuple(arg_raws), key)
        for (p, _), s in zip(nstate_cell["state_params"], state_raws):
            p._nd._data = s
        rebuild = rebuild_cell["rebuild"]
        if _ag.is_recording():
            node_inputs = [p._nd for p in plist] + [a for a in args if isinstance(a, NDArray)]
            nd_positions = [i for i, a in enumerate(args) if isinstance(a, NDArray)]
            const_args = list(arg_raws)

            def replay_op(*flat, _np=len(plist), _key=key, _consts=const_args,
                          _pos=nd_positions, _jfn=jfn):
                pr = tuple(flat[:_np])
                ar = list(_consts)
                for p_i, v in zip(_pos, flat[_np:]):
                    ar[p_i] = v
                outs, _states = _jfn(pr, tuple(ar), _key)
                return tuple(outs)

            node = _ag.TapeNode(replay_op, {}, node_inputs, len(out_raws), self.name)
            wrapped = []
            for i, r in enumerate(out_raws):
                w = NDArray(r)
                w._tape = (node, i)
                wrapped.append(w)
            return rebuild(wrapped)
        return rebuild(list(out_raws))

    def _build_jit(self, plist, args, train):
        rebuild_cell = {"rebuild": None}
        nstate_cell = {"state_params": []}
        arg_is_nd = [isinstance(a, NDArray) for a in args]

        def pure(param_raws, arg_raws, key):
            with _HybridTrace(plist, param_raws, train, key) as tr:
                call_args = [NDArray(r) if is_nd else r
                             for r, is_nd in zip(arg_raws, arg_is_nd)]
                out = Block.__call__(self, *call_args)
                raws, rebuild = _flatten_nds(out)
            rebuild_cell["rebuild"] = rebuild
            nstate_cell["state_params"] = [(p, None) for p, _ in tr.state_updates]
            nstate_cell["uses_rng"] = tr.rng_uses > 0
            states = tuple(jax.lax.stop_gradient(s) for _, s in tr.state_updates)
            return tuple(raws), states

        return jax.jit(pure), rebuild_cell, nstate_cell

    # -- deployment (reference: HybridBlock.export -> symbol.json + params) --
    def trace_symbol(self, *input_names):
        """Trace this block's forward into a Symbol graph (parameters become
        named variables). The reference got the same artifact from the
        CachedOp's nnvm graph."""
        from .. import symbol as sym_mod

        input_names = input_names or ("data",)
        saved = _TRACE.symbolic
        _TRACE.symbolic = True
        try:
            out = Block.__call__(self, *[sym_mod.var(n) for n in input_names])
        finally:
            _TRACE.symbolic = saved
        return out

    def export(self, path, epoch=0, input_names=("data",)):
        """Write ``path-symbol.json`` + ``path-{epoch}.params`` (reference
        deploy format: arg:-prefixed names)."""
        from ..serialization import save_ndarrays

        out = self.trace_symbol(*input_names)
        if isinstance(out, (tuple, list)):
            from .. import symbol as sym_mod

            out = sym_mod.Group(list(out))
        out.save(f"{path}-symbol.json")
        fname = f"{path}-{epoch:04d}.params"
        by_name = {p.name: p for p in self.collect_params().values()
                   if p._nd is not None}
        save_ndarrays(fname, {("arg:" + k): p.data() for k, p in by_name.items()})
        return f"{path}-symbol.json", fname


class SymbolBlock(Block):
    """Runs an exported symbol.json graph (reference: deploy path —
    ``SymbolBlock.imports(sym, ['data'], params_file)``)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="symbolblock_", params=None)
        from .. import symbol as sym_mod

        self._out_symbol = outputs
        self._input_names = [i.name if isinstance(i, sym_mod.Symbol) else i
                             for i in (inputs if isinstance(inputs, (list, tuple))
                                       else [inputs])]
        arg_names = outputs.list_arguments()
        for name in arg_names:
            if name in self._input_names:
                continue
            p = Parameter(name, allow_deferred_init=True)
            self._params._params[name] = p
            if params and name in params:
                p.set_data(params[name])

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from .. import symbol as sym_mod
        from ..serialization import load_ndarrays

        out = sym_mod.load(symbol_file)
        params = {}
        if param_file:
            loaded = load_ndarrays(param_file)
            params = {k.removeprefix("arg:").removeprefix("aux:"): v
                      for k, v in loaded.items()}
        if isinstance(input_names, str):
            input_names = [input_names]
        return SymbolBlock(out, input_names, params)

    def forward(self, *args):
        from .. import symbol as sym_mod

        env = dict(zip(self._input_names, args))
        for name, p in self._params.items():
            if p._nd is not None:
                env[name] = p.data()
        return sym_mod.eval_symbol(self._out_symbol, env)
