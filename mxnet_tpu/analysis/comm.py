"""Communication cost model over a program's collectives.

The HLO auditor inventories *which* collectives a compiled program runs;
this module prices them. Each collective gets a logical byte cost from its
payload size and replica-group span, attributed to the mesh axes the
group actually crosses — so "the dp gradient all-reduce moves
2 x param-bytes over the dp axis and nothing else" is a structural
assertion, and a mis-specified sharding that turns a reduce-scatter
pattern into replicated all-gathers (arXiv:2004.13336's failure mode)
shows up as a byte regression on the wrong axis.

Cost convention (documented, deliberately simple — logical bytes of the
bandwidth-optimal algorithm, not a hardware model):

  =====================  =================================================
  all_reduce             2 x full tensor bytes (reduce-scatter + all-gather
                         halves of the ring)
  all_gather             1 x full tensor bytes (operand shard x group span)
  reduce_scatter         1 x full tensor bytes (the pre-scatter input)
  all_to_all             1 x tensor bytes
  collective_permute     1 x tensor bytes (one ICI hop per pair)
  collective_broadcast   1 x tensor bytes
  =====================  =================================================

Async start/done pairs were already collapsed to ONE collective by the
parser, so nothing here double-counts. Collectives inside a ``lax.scan``
body (the fused k-step window) appear once in the program text and are
counted once — the report is a static per-dispatch census, not a trace.

Axis attribution maps each normalized replica group onto the mesh: the
axes whose coordinates vary inside a group are the axes the collective
spans. Groups that cannot be resolved (no mesh, ``source_target_pairs``
collectives, exotic iota forms) land under the ``"?"`` axis key with their
bytes intact — unattributed traffic is still traffic.

Also here: the accidental-reshard detector. An ``all_gather`` whose full
result exactly matches the global shape of a tensor the sharding rules
*declared* sharded — and that is not on the intended gather list (the
ZeRO compute-spec params TrainStep gathers on purpose) — means GSPMD is
silently materializing the tensor every step.
"""
from __future__ import annotations

import dataclasses
from collections import Counter as _Counter
from typing import Dict, List, Optional, Sequence, Tuple

from .hlo_audit import DTYPE_BYTES  # noqa: F401  (canonical home moved)
from .hlo_audit import Collective, ProgramReport

__all__ = ["CollectiveCost", "CommReport", "Reshard", "comm_report",
           "detect_accidental_reshards", "DTYPE_BYTES"]

# byte multiplier per collective kind (see module docstring table)
_KIND_FACTOR = {
    "all_reduce": 2, "all_gather": 1, "reduce_scatter": 1, "all_to_all": 1,
    "collective_permute": 1, "collective_broadcast": 1,
}


def _elems(shape: Tuple[int, ...]) -> int:
    n = 1
    for d in shape:
        n *= d
    return n


def _tensors_bytes(info: Sequence[Tuple[str, Tuple[int, ...]]]) -> int:
    return sum(_elems(sh) * DTYPE_BYTES.get(dt, 4) for dt, sh in info)


def _payload_bytes(c: Collective) -> int:
    """Full-tensor logical payload of one collective, before the per-kind
    factor. Operand-side sizing survives both the sync and the
    tuple-result async-start spellings (a start op's operands are exactly
    the payloads; its result tuple carries bookkeeping scalars)."""
    opd = _tensors_bytes(c.operand_info)
    if c.name == "all_gather":
        # operand is the shard; the full tensor is shard x group span
        if opd and c.group_size:
            return opd * c.group_size
        # fall back to the largest result tensor (the gathered output)
        if c.result_info:
            return max(_elems(sh) * DTYPE_BYTES.get(dt, 4)
                       for dt, sh in c.result_info)
        return opd
    if c.name == "reduce_scatter" and opd == 0 and c.result_info \
            and c.group_size:
        return _tensors_bytes(c.result_info) * c.group_size
    if opd:
        return opd
    # no operand info (best-effort MLIR region ops): result side, else the
    # op's own shape/dtype
    if c.result_info:
        return _tensors_bytes(c.result_info)
    if c.dtype is not None:
        return _elems(c.shape) * DTYPE_BYTES.get(c.dtype, 4)
    return 0


def _axes_for_groups(groups, mesh) -> Tuple[str, ...]:
    """Mesh axes a replica grouping spans: the axes whose coordinates vary
    inside a group. () when unresolvable (no mesh / out-of-range ids).

    Replica-group entries are LOGICAL ids — positions in the program's
    device assignment, which for a jitted mesh program is ``mesh.devices``
    flattened — NOT ``Device.id``. The two coincide on a single process,
    but multi-process backends number real devices sparsely (CPU:
    ``process_index << 17``), so a ``Device.id`` lookup would silently
    unattribute every cross-host collective."""
    if not groups or mesh is None:
        return ()
    import numpy as np

    shape = tuple(mesh.devices.shape)
    size = int(mesh.devices.size)
    names = list(mesh.shape)
    varying = set()
    for g in groups:
        if any(d < 0 or d >= size for d in g):
            return ()
        coords = [np.unravel_index(d, shape) for d in g]
        for ax_i in range(len(names)):
            if len({c[ax_i] for c in coords}) > 1:
                varying.add(ax_i)
    return tuple(n for i, n in enumerate(names) if i in varying)


@dataclasses.dataclass
class CollectiveCost:
    """One priced collective: kind, payload, span, and the mesh axes it
    crosses (``()`` = unattributed, rendered as ``"?"``)."""

    kind: str
    dtype: Optional[str]
    payload_bytes: int  # full logical tensor bytes (pre-factor)
    bytes: int  # payload x per-kind factor (all_reduce counts 2x)
    group_size: Optional[int]
    n_groups: Optional[int]
    axes: Tuple[str, ...]
    line: int

    @property
    def axis_key(self) -> str:
        return "×".join(self.axes) if self.axes else "?"


@dataclasses.dataclass
class Reshard:
    """A GSPMD-inserted all-gather that fully materializes a tensor the
    rules declared sharded (and that was not an intended compute
    gather) — the silent replication arXiv:2004.13336 warns about."""

    param: str
    kind: str
    bytes: int
    line: int

    def __str__(self):
        return (f"{self.param}: declared sharded but a {self.kind} at "
                f"L{self.line} fully materializes it ({self.bytes} bytes)")


@dataclasses.dataclass
class CommReport:
    """Per-program communication census: every collective priced, rolled
    up by mesh axis and by kind (docs/ANALYSIS.md). Truthy iff any
    collective was found."""

    costs: List[CollectiveCost] = dataclasses.field(default_factory=list)
    reshards: List[Reshard] = dataclasses.field(default_factory=list)

    def __bool__(self):
        return bool(self.costs)

    def total_bytes(self) -> int:
        return sum(c.bytes for c in self.costs)

    def by_axis(self) -> Dict[str, int]:
        out: _Counter = _Counter()
        for c in self.costs:
            out[c.axis_key] += c.bytes
        return dict(out)

    def by_kind(self) -> Dict[str, int]:
        out: _Counter = _Counter()
        for c in self.costs:
            out[c.kind] += c.bytes
        return dict(out)

    def kind_counts(self) -> Dict[str, int]:
        return dict(_Counter(c.kind for c in self.costs))

    def summary(self) -> dict:
        return {
            "n_collectives": len(self.costs),
            "total_bytes": self.total_bytes(),
            "by_axis": self.by_axis(),
            "by_kind": self.by_kind(),
            "kind_counts": self.kind_counts(),
            "accidental_reshards": [str(r) for r in self.reshards],
        }


def comm_report(report: ProgramReport, mesh=None) -> CommReport:
    """Price every collective in ``report``. ``mesh`` (a
    ``jax.sharding.Mesh``, optional) enables axis attribution — without
    it all traffic lands under ``"?"``."""
    costs = []
    for c in report.collectives:
        payload = _payload_bytes(c)
        factor = _KIND_FACTOR.get(c.name, 1)
        costs.append(CollectiveCost(
            kind=c.name, dtype=c.dtype, payload_bytes=payload,
            bytes=payload * factor, group_size=c.group_size,
            n_groups=len(c.groups) if c.groups else None,
            axes=_axes_for_groups(c.groups, mesh), line=c.line))
    return CommReport(costs=costs)


def detect_accidental_reshards(
        report: ProgramReport,
        declared_specs: Dict[str, object],
        shapes: Dict[str, Tuple[int, ...]],
        intended: Optional[set] = None,
        mesh=None) -> List[Reshard]:
    """All-gathers whose full result matches the *global* shape of a
    declared-sharded tensor not on the ``intended`` gather list.

    ``declared_specs`` maps name -> PartitionSpec (entries iterable;
    anything with a non-None entry counts as declared-sharded),
    ``shapes`` maps name -> global shape, ``intended`` names tensors the
    caller gathers on purpose (TrainStep's ZeRO compute-spec params).

    Matching is a shape heuristic, tightened two ways against false CI
    failures: a shape shared between an intended and a non-intended
    tensor is ambiguous and skipped entirely; and with ``mesh`` given,
    the gather's *operand* must also match the shard shape the declared
    spec implies (global dims / expected tiles), so e.g. an activation
    gather whose result merely coincides with a square weight's global
    shape is not pinned on the weight. A missed flag on a correct
    program beats failing the shardcheck gate on a coincidence."""
    intended = intended or set()
    intended_shapes = {tuple(shapes[n]) for n in intended if n in shapes}
    mesh_shape = dict(mesh.shape) if mesh is not None else None
    watch: Dict[Tuple[int, ...], List[Tuple[str, object]]] = {}
    for name, spec in declared_specs.items():
        if name in intended:
            continue
        shape = tuple(shapes[name])
        if shape in intended_shapes:
            continue
        if any(e is not None for e in tuple(spec)):
            watch.setdefault(shape, []).append((name, spec))
    if not watch:
        return []

    def shard_shape(shape, spec):
        from .contract import expected_tiles

        tiles = expected_tiles(spec, len(shape), mesh_shape)
        if tiles is None or any(d % t for d, t in zip(shape, tiles)):
            return None
        return tuple(d // t for d, t in zip(shape, tiles))

    out: List[Reshard] = []
    for c in report.collectives:
        if c.name != "all_gather":
            continue
        full = max((sh for _, sh in c.result_info), key=_elems,
                   default=c.shape)
        opd_shapes = {sh for _, sh in c.operand_info}
        for name, spec in watch.get(tuple(full), []):
            if mesh_shape is not None and opd_shapes:
                want = shard_shape(tuple(full), spec)
                if want is not None and want not in opd_shapes:
                    continue
            out.append(Reshard(param=name, kind=c.name,
                               bytes=_payload_bytes(c), line=c.line))
    return out
