"""ResNet v1/v2 (reference: ``python/mxnet/gluon/model_zoo/vision/resnet.py``).

Driver config #2 model (BASELINE.md). Public layout stays NCHW like the
reference; XLA re-layouts convs for the MXU internally.
"""
from __future__ import annotations

from ...block import HybridBlock
from ...nn import (Activation, AvgPool2D, BatchNorm, Conv2D, Dense, Flatten,
                   GlobalAvgPool2D, HybridSequential, MaxPool2D)

__all__ = ["ResNetV1", "ResNetV2", "get_resnet",
           "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1", "resnet152_v1",
           "resnet18_v2", "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2"]


def _conv3x3(channels, stride, in_channels):
    return Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                  use_bias=False, in_channels=in_channels)


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.body = HybridSequential(prefix="")
        self.body.add(_conv3x3(channels, stride, in_channels))
        self.body.add(BatchNorm())
        self.body.add(Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels))
        self.body.add(BatchNorm())
        if downsample:
            self.downsample = HybridSequential(prefix="")
            self.downsample.add(Conv2D(channels, kernel_size=1, strides=stride,
                                       use_bias=False, in_channels=in_channels))
            self.downsample.add(BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(residual + x, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.body = HybridSequential(prefix="")
        self.body.add(Conv2D(channels // 4, kernel_size=1, strides=stride))
        self.body.add(BatchNorm())
        self.body.add(Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4))
        self.body.add(BatchNorm())
        self.body.add(Activation("relu"))
        self.body.add(Conv2D(channels, kernel_size=1, strides=1))
        self.body.add(BatchNorm())
        if downsample:
            self.downsample = HybridSequential(prefix="")
            self.downsample.add(Conv2D(channels, kernel_size=1, strides=stride,
                                       use_bias=False, in_channels=in_channels))
            self.downsample.add(BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(x + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.bn1 = BatchNorm()
        self.conv1 = _conv3x3(channels, stride, in_channels)
        self.bn2 = BatchNorm()
        self.conv2 = _conv3x3(channels, 1, channels)
        self.ds = (Conv2D(channels, 1, stride, use_bias=False, in_channels=in_channels)
                   if downsample else None)

    def hybrid_forward(self, F, x):
        residual = x
        x = F.Activation(self.bn1(x), act_type="relu")
        if self.ds:
            residual = self.ds(x)
        x = self.conv1(x)
        x = F.Activation(self.bn2(x), act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.bn1 = BatchNorm()
        self.conv1 = Conv2D(channels // 4, 1, 1, use_bias=False)
        self.bn2 = BatchNorm()
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4)
        self.bn3 = BatchNorm()
        self.conv3 = Conv2D(channels, 1, 1, use_bias=False)
        self.ds = (Conv2D(channels, 1, stride, use_bias=False, in_channels=in_channels)
                   if downsample else None)

    def hybrid_forward(self, F, x):
        residual = x
        x = F.Activation(self.bn1(x), act_type="relu")
        if self.ds:
            residual = self.ds(x)
        x = self.conv1(x)
        x = F.Activation(self.bn2(x), act_type="relu")
        x = self.conv2(x)
        x = F.Activation(self.bn3(x), act_type="relu")
        x = self.conv3(x)
        return x + residual


resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = HybridSequential(prefix="")
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0))
            else:
                self.features.add(Conv2D(channels[0], 7, 2, 3, use_bias=False))
                self.features.add(BatchNorm())
                self.features.add(Activation("relu"))
                self.features.add(MaxPool2D(3, 2, 1))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(block, num_layer, channels[i + 1],
                                                   stride, i + 1, channels[i]))
            self.features.add(GlobalAvgPool2D())
            self.output = Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, stage_index, in_channels=0):
        layer = HybridSequential(prefix=f"stage{stage_index}_")
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels, prefix=""))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = HybridSequential(prefix="")
            self.features.add(BatchNorm(scale=False, center=False))
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0))
            else:
                self.features.add(Conv2D(channels[0], 7, 2, 3, use_bias=False))
                self.features.add(BatchNorm())
                self.features.add(Activation("relu"))
                self.features.add(MaxPool2D(3, 2, 1))
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(block, num_layer, channels[i + 1],
                                                   stride, i + 1, in_channels))
                in_channels = channels[i + 1]
            self.features.add(BatchNorm())
            self.features.add(Activation("relu"))
            self.features.add(GlobalAvgPool2D())
            self.features.add(Flatten())
            self.output = Dense(classes, in_units=in_channels)

    _make_layer = ResNetV1._make_layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


_blocks_v1 = {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1}
_blocks_v2 = {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2}


def get_resnet(version, num_layers, pretrained=False, ctx=None, **kwargs):
    block_type, layers, channels = resnet_spec[num_layers]
    if version == 1:
        return ResNetV1(_blocks_v1[block_type], layers, channels, **kwargs)
    return ResNetV2(_blocks_v2[block_type], layers, channels, **kwargs)


def resnet18_v1(**kw): return get_resnet(1, 18, **kw)
def resnet34_v1(**kw): return get_resnet(1, 34, **kw)
def resnet50_v1(**kw): return get_resnet(1, 50, **kw)
def resnet101_v1(**kw): return get_resnet(1, 101, **kw)
def resnet152_v1(**kw): return get_resnet(1, 152, **kw)
def resnet18_v2(**kw): return get_resnet(2, 18, **kw)
def resnet34_v2(**kw): return get_resnet(2, 34, **kw)
def resnet50_v2(**kw): return get_resnet(2, 50, **kw)
def resnet101_v2(**kw): return get_resnet(2, 101, **kw)
def resnet152_v2(**kw): return get_resnet(2, 152, **kw)
