"""ctypes bindings to the native runtime library (``native/``).

The reference's rule — one flat C ABI under every binding — is kept: the
library exports ``MXTPU*`` functions with int/handle returns and a
thread-local ``MXTPUGetLastError``. Python stays fully functional without
the library (pure-Python fallbacks); when present, RecordIO reads go through
the C++ engine with its threaded prefetcher.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

__all__ = ["lib", "available", "ensure_built", "NativeRecordReader",
           "NativeRecordWriter", "NativePrefetchReader"]

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _lib_path():
    return os.path.join(os.path.dirname(__file__), "_native", "libmxtpu.so")


def ensure_built(quiet=True) -> bool:
    """Build the native library with make if a toolchain is available."""
    if os.path.exists(_lib_path()):
        return True
    native_dir = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
    if not os.path.isdir(native_dir):
        return False
    try:
        subprocess.run(["make", "-C", native_dir], check=True,
                       capture_output=quiet, timeout=120)
        return os.path.exists(_lib_path())
    except Exception:
        return False


def lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    if not ensure_built():
        return None
    try:
        L = ctypes.CDLL(_lib_path())
    except OSError:
        return None
    L.MXTPUGetLastError.restype = ctypes.c_char_p
    L.MXTPURecordWriterCreate.restype = ctypes.c_void_p
    L.MXTPURecordWriterCreate.argtypes = [ctypes.c_char_p]
    L.MXTPURecordWriterWrite.restype = ctypes.c_int64
    L.MXTPURecordWriterWrite.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
    L.MXTPURecordWriterFree.argtypes = [ctypes.c_void_p]
    L.MXTPURecordReaderCreate.restype = ctypes.c_void_p
    L.MXTPURecordReaderCreate.argtypes = [ctypes.c_char_p]
    L.MXTPURecordReaderSeek.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    L.MXTPURecordReaderNext.restype = ctypes.c_int64
    L.MXTPURecordReaderNext.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
    L.MXTPURecordReaderFree.argtypes = [ctypes.c_void_p]
    L.MXTPUPrefetchCreate.restype = ctypes.c_void_p
    L.MXTPUPrefetchCreate.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                                      ctypes.c_uint64, ctypes.c_int, ctypes.c_uint64]
    L.MXTPUPrefetchNext.restype = ctypes.c_int64
    L.MXTPUPrefetchNext.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
    L.MXTPUPrefetchFree.argtypes = [ctypes.c_void_p]
    _LIB = L
    return _LIB


def available() -> bool:
    return lib() is not None


class NativeRecordWriter:
    def __init__(self, path):
        L = lib()
        if L is None:
            raise RuntimeError("native library unavailable")
        self._L = L
        self._h = L.MXTPURecordWriterCreate(path.encode())
        if not self._h:
            raise IOError(L.MXTPUGetLastError().decode())

    def write(self, buf: bytes) -> int:
        pos = self._L.MXTPURecordWriterWrite(self._h, buf, len(buf))
        if pos < 0:
            raise IOError(self._L.MXTPUGetLastError().decode())
        return pos

    def close(self):
        if self._h:
            self._L.MXTPURecordWriterFree(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeRecordReader:
    def __init__(self, path):
        L = lib()
        if L is None:
            raise RuntimeError("native library unavailable")
        self._L = L
        self._h = L.MXTPURecordReaderCreate(path.encode())
        if not self._h:
            raise IOError(L.MXTPUGetLastError().decode())

    def seek(self, pos: int):
        self._L.MXTPURecordReaderSeek(self._h, pos)

    def read(self):
        ptr = ctypes.POINTER(ctypes.c_uint8)()
        n = self._L.MXTPURecordReaderNext(self._h, ctypes.byref(ptr))
        if n == -2:
            return None
        if n < 0:
            raise IOError(self._L.MXTPUGetLastError().decode())
        return ctypes.string_at(ptr, n)

    def close(self):
        if self._h:
            self._L.MXTPURecordReaderFree(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativePrefetchReader:
    """Multi-threaded in-order record prefetcher over known offsets."""

    def __init__(self, path, offsets, num_threads=4, queue_cap=64):
        L = lib()
        if L is None:
            raise RuntimeError("native library unavailable")
        self._L = L
        arr = (ctypes.c_int64 * len(offsets))(*offsets)
        self._h = L.MXTPUPrefetchCreate(path.encode(), arr, len(offsets),
                                        num_threads, queue_cap)

    def __iter__(self):
        return self

    def __next__(self):
        ptr = ctypes.POINTER(ctypes.c_uint8)()
        n = self._L.MXTPUPrefetchNext(self._h, ctypes.byref(ptr))
        if n == -2:
            self.close()
            raise StopIteration
        return ctypes.string_at(ptr, n)

    def close(self):
        if self._h:
            self._L.MXTPUPrefetchFree(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
