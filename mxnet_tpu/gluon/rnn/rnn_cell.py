"""Unfused RNN cells (reference: ``python/mxnet/gluon/rnn/rnn_cell.py``)."""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["RNNCell", "LSTMCell", "GRUCell", "SequentialRNNCell"]


class _BaseCell(HybridBlock):
    def __init__(self, hidden_size, input_size=0, ngates=1, prefix=None, params=None,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros"):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._ng = ngates
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight", shape=(ngates * hidden_size, input_size),
                                              init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get("h2h_weight", shape=(ngates * hidden_size, hidden_size),
                                              init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get("i2h_bias", shape=(ngates * hidden_size,),
                                            init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get("h2h_bias", shape=(ngates * hidden_size,),
                                            init=h2h_bias_initializer, allow_deferred_init=True)

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._ng * self._hidden_size, x.shape[-1])

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as nd

        n = 2 if isinstance(self, LSTMCell) else 1
        return [nd.zeros((batch_size, self._hidden_size)) for _ in range(n)]

    def unroll(self, length, inputs, begin_state=None, layout="NTC", merge_outputs=None,
               valid_length=None):
        from ... import ndarray as nd

        axis = layout.find("T")
        states = begin_state or self.begin_state(inputs.shape[1 - axis if axis == 0 else 0])
        outputs = []
        for t in range(length):
            x_t = inputs.slice_axis(axis=axis, begin=t, end=t + 1).squeeze(axis=axis)
            out, states = self(x_t, states)
            outputs.append(out)
        if merge_outputs or merge_outputs is None:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states


class RNNCell(_BaseCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0, **kwargs):
        super().__init__(hidden_size, input_size, 1, **kwargs)
        self._activation = activation

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        h = states[0] if isinstance(states, (list, tuple)) else states
        out = F.Activation(
            F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=self._hidden_size)
            + F.FullyConnected(h, h2h_weight, h2h_bias, num_hidden=self._hidden_size),
            act_type=self._activation)
        return out, [out]


class LSTMCell(_BaseCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, input_size, 4, **kwargs)

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        h, c = states
        gates = (F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=4 * self._hidden_size)
                 + F.FullyConnected(h, h2h_weight, h2h_bias, num_hidden=4 * self._hidden_size))
        i, f, g, o = F.split(gates, num_outputs=4, axis=-1)
        c_new = F.sigmoid(f) * c + F.sigmoid(i) * F.tanh(g)
        h_new = F.sigmoid(o) * F.tanh(c_new)
        return h_new, [h_new, c_new]


class GRUCell(_BaseCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, input_size, 3, **kwargs)

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        h = states[0] if isinstance(states, (list, tuple)) else states
        xz = F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=3 * self._hidden_size)
        hz = F.FullyConnected(h, h2h_weight, h2h_bias, num_hidden=3 * self._hidden_size)
        xr, xu, xn = F.split(xz, num_outputs=3, axis=-1)
        hr, hu, hn = F.split(hz, num_outputs=3, axis=-1)
        r = F.sigmoid(xr + hr)
        u = F.sigmoid(xu + hu)
        n = F.tanh(xn + r * hn)
        h_new = (1 - u) * n + u * h
        return h_new, [h_new]


class SequentialRNNCell(_BaseCell):
    def __init__(self, prefix=None, params=None):
        HybridBlock.__init__(self, prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def begin_state(self, batch_size=0, **kwargs):
        states = []
        for c in self._children.values():
            states.append(c.begin_state(batch_size, **kwargs))
        return states

    def hybrid_forward(self, F, x, states):
        next_states = []
        for cell, s in zip(self._children.values(), states):
            x, ns = cell(x, s)
            next_states.append(ns)
        return x, next_states
