"""Model zoo forward shapes (reference: tests/python/unittest/test_gluon_model_zoo.py)."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # model compiles dominate `make test`; excluded from `make fast`

from mxnet_tpu import gluon, nd


@pytest.mark.parametrize("name,size", [
    ("resnet34_v2", 32), ("vgg11", 32), ("vgg11_bn", 32),
    ("mobilenet0.25", 32), ("mobilenetv2_0.5", 32),
    ("squeezenet1.1", 64), ("densenet121", 32), ("alexnet", 224),
    ("inceptionv3", 299), ("resnext50_32x4d", 64), ("se_resnext50_32x4d", 64),
])
def test_zoo_forward(name, size):
    net = gluon.model_zoo.get_model(name, classes=11)
    net.initialize()
    out = net(nd.ones((1, 3, size, size)))
    assert out.shape == (1, 11), name


def test_zoo_unknown_model():
    with pytest.raises(ValueError, match="not in zoo"):
        gluon.model_zoo.get_model("resnext9000")


@pytest.mark.parametrize("name,size", [
    ("lenet", 28), ("resnet18_v1", 32), ("vgg11", 32), ("alexnet", 224),
    ("squeezenet1.0", 64), ("densenet121", 32), ("inceptionv3", 299),
    ("mobilenet0.25", 32), ("se_resnext50_32x4d", 64),
])
def test_zoo_hybridize_equivalence(name, size):
    """Eager forward == hybridized forward for every zoo family — THE core
    invariant of the hybridize()->jit bridge (SURVEY §4 fixture #4)."""
    import mxnet_tpu as mx

    mx.random.seed(7)
    net = gluon.model_zoo.get_model(name, classes=7)
    net.initialize()
    chans = 1 if name == "lenet" else 3
    x = nd.array(np.random.RandomState(0).rand(2, chans, size, size)
                 .astype(np.float32))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()       # first call: trace+compile
    hybrid2 = net(x).asnumpy()      # second call: cached program
    np.testing.assert_allclose(eager, hybrid, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(hybrid, hybrid2, rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("name", ["resnet18_v1", "mobilenetv2_0.5"])
def test_zoo_train_mode_grads(name):
    """BatchNorm train-mode forward + backward through two zoo families."""
    from mxnet_tpu import autograd

    net = gluon.model_zoo.get_model(name, classes=4)
    net.initialize()
    # random input: a constant input is degenerate under BatchNorm (zero
    # variance -> zero activations -> exactly-zero loss gradient)
    x = nd.array(np.random.RandomState(1).rand(2, 3, 32, 32).astype(np.float32))
    with autograd.record():
        out = net(x)
        loss = (out ** 2).mean()
    loss.backward()
    total = 0.0
    for _, p in net.collect_params().items():
        if p.grad_req != "null" and p._nd is not None:
            total += float(abs(p.grad().asnumpy()).sum())
    assert np.isfinite(total) and total > 0
