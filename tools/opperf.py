#!/usr/bin/env python
"""Per-operator micro-benchmark runner.

Reference analog: ``benchmark/opperf/opperf.py`` — the suite that produced
the reference's per-op latency tables (BASELINE.md). Runs each registry op
on representative shapes, reporting median wall time over timed reps with a
jit-warmup first (compile excluded, like the reference's warmup).

Usage:
  python tools/opperf.py                      # default op set
  python tools/opperf.py --ops dot,softmax    # subset
  python tools/opperf.py --json results.json  # machine-readable dump
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# representative shapes per op family (reference: opperf's DEFAULT_* shapes,
# scaled to finish quickly on any backend)
_CASES = {
    "dot": lambda nd: (nd.array(np.random.rand(256, 256).astype(np.float32)),
                       nd.array(np.random.rand(256, 256).astype(np.float32))),
    "batch_dot": lambda nd: (nd.array(np.random.rand(8, 128, 128).astype(np.float32)),
                             nd.array(np.random.rand(8, 128, 128).astype(np.float32))),
    "add": lambda nd: (nd.array(np.random.rand(512, 512).astype(np.float32)),
                       nd.array(np.random.rand(512, 512).astype(np.float32))),
    "multiply": lambda nd: (nd.array(np.random.rand(512, 512).astype(np.float32)),
                            nd.array(np.random.rand(512, 512).astype(np.float32))),
    "exp": lambda nd: (nd.array(np.random.rand(512, 512).astype(np.float32)),),
    "tanh": lambda nd: (nd.array(np.random.rand(512, 512).astype(np.float32)),),
    "relu": lambda nd: (nd.array(np.random.rand(512, 512).astype(np.float32)),),
    "sigmoid": lambda nd: (nd.array(np.random.rand(512, 512).astype(np.float32)),),
    "softmax": lambda nd: (nd.array(np.random.rand(128, 1024).astype(np.float32)),),
    "log_softmax": lambda nd: (nd.array(np.random.rand(128, 1024).astype(np.float32)),),
    "sum": lambda nd: (nd.array(np.random.rand(512, 512).astype(np.float32)),),
    "mean": lambda nd: (nd.array(np.random.rand(512, 512).astype(np.float32)),),
    "transpose": lambda nd: (nd.array(np.random.rand(256, 512).astype(np.float32)),),
    "concat": lambda nd: (nd.array(np.random.rand(256, 256).astype(np.float32)),
                          nd.array(np.random.rand(256, 256).astype(np.float32))),
    "take": lambda nd: (nd.array(np.random.rand(1024, 64).astype(np.float32)),
                        nd.array(np.random.randint(0, 1024, 256), dtype="int32")),
    "LayerNorm": lambda nd: (nd.array(np.random.rand(128, 768).astype(np.float32)),
                             nd.ones((768,)), nd.zeros((768,))),
    "FullyConnected": lambda nd: (
        nd.array(np.random.rand(128, 512).astype(np.float32)),
        nd.array(np.random.rand(256, 512).astype(np.float32)),
        nd.array(np.random.rand(256).astype(np.float32))),
    "Convolution": lambda nd: (
        nd.array(np.random.rand(8, 16, 32, 32).astype(np.float32)),
        nd.array(np.random.rand(32, 16, 3, 3).astype(np.float32)),
        nd.array(np.random.rand(32).astype(np.float32))),
    "linalg_potrf": lambda nd: (nd.array(
        (lambda a: a @ a.T + 64 * np.eye(64, dtype=np.float32))(
            np.random.rand(64, 64).astype(np.float32))),),
    "linalg_gemm2": lambda nd: (nd.array(np.random.rand(8, 128, 128).astype(np.float32)),
                                nd.array(np.random.rand(8, 128, 128).astype(np.float32))),
    "adam_update": lambda nd: (
        nd.array(np.random.rand(512, 512).astype(np.float32)),
        nd.array(np.random.rand(512, 512).astype(np.float32)),
        nd.array((np.random.rand(512, 512) * 0.1).astype(np.float32)),
        nd.array((np.abs(np.random.rand(512, 512)) * 0.01).astype(np.float32))),
    "softmax_cross_entropy_fused": lambda nd: (
        nd.array(np.random.rand(128, 1024).astype(np.float32)),
        nd.array(np.random.randint(0, 1024, 128), dtype="int32")),
    "paged_attention": lambda nd: _paged_attention_case(),
}


def _paged_attention_case():
    """Engine-internal surface (no nd registry entry): the paged decode
    read path at the genbench decode shape — f32 activations, bf16 pool."""
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    b, h, ch, ps, n_pages = 8, 2, 32, 16, 8
    pool_pages = b * n_pages
    return (jnp.asarray(rng.randn(b, h, 1, ch), jnp.float32),
            jnp.asarray(rng.randn(b, h, 1, ch), jnp.float32),
            jnp.asarray(rng.randn(b, h, 1, ch), jnp.float32),
            jnp.asarray(rng.randn(pool_pages + 1, h, ps, ch), jnp.bfloat16),
            jnp.asarray(rng.randn(pool_pages + 1, h, ps, ch), jnp.bfloat16),
            jnp.asarray(rng.randint(1, pool_pages + 1, (b, n_pages)),
                        jnp.int32),
            jnp.asarray(rng.randint(0, n_pages * ps - 1, (b,)), jnp.int32))


# kernel surfaces that live below the nd registry (the engine calls them
# directly); benched on raw jax arrays
def _extra_fn(name):
    if name == "paged_attention":
        import jax

        from mxnet_tpu.ops import pallas_paged_attention as ppa

        return jax.jit(ppa.paged_attention)
    raise KeyError(name)

_KWARGS = {
    "FullyConnected": {"num_hidden": 256},
    "Convolution": {"num_filter": 32, "kernel": (3, 3)},
    "concat": {"dim": 1},
    "adam_update": {"lr": 0.001},
}


def _sync(out):
    o = out[0] if isinstance(out, (tuple, list)) else out
    if hasattr(o, "wait_to_read"):
        o.wait_to_read()
    else:
        o.block_until_ready()


def bench_op(name, reps=20, warmup=3):
    from mxnet_tpu import nd

    mk = _CASES[name]
    args = mk(nd)
    kwargs = _KWARGS.get(name, {})
    fn = getattr(nd, name, None) or _extra_fn(name)
    for _ in range(warmup):
        out = fn(*args, **kwargs)
    _sync(out)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        _sync(out)
        times.append(time.perf_counter() - t0)
    times.sort()
    return {"op": name, "p50_us": round(times[len(times) // 2] * 1e6, 1),
            "min_us": round(times[0] * 1e6, 1),
            "max_us": round(times[-1] * 1e6, 1), "reps": reps}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default="", help="comma-separated subset")
    ap.add_argument("--reps", type=int, default=20)
    ap.add_argument("--json", default="", help="write results to this file")
    ap.add_argument("--platform", default="",
                    help="force a jax platform (e.g. cpu) before backend init")
    args = ap.parse_args()

    if args.platform:
        # must happen before the first backend touch; the axon sitecustomize
        # pre-imports jax, so go through jax.config (env vars are too late)
        import jax

        jax.config.update("jax_platforms", args.platform)

    names = [o for o in args.ops.split(",") if o] or sorted(_CASES)
    unknown = [n for n in names if n not in _CASES]
    if unknown:
        ap.error(f"no benchmark case for: {unknown}; known: {sorted(_CASES)}")

    import mxnet_tpu as mx

    mx.random.seed(0)
    results = [bench_op(n, reps=args.reps) for n in names]
    header = f"{'Operator':<20} {'p50(us)':>10} {'min(us)':>10} {'max(us)':>10}"
    print(header)
    print("-" * len(header))
    for r in results:
        print(f"{r['op']:<20} {r['p50_us']:>10} {r['min_us']:>10} {r['max_us']:>10}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
        print(f"wrote {args.json}")


if __name__ == "__main__":
    main()
